"""H17/H18/H19 — the RacerD-style lock-consistency rules.

These run on two layers at once: the PR-8/9 lock model (which lock is
held at each statement, with class-scoped lock identity) and the
thread topology (``threads.py`` — which OS threads may execute each
function, with a witness chain to every root). Neither layer alone
can see a race: the lock model has no threads, the topology has no
locks. Together they support the classic guarded-by argument.

**Guarded-by inference.** For each class attribute the package
touches (``self.X`` accesses collected per ``module::Class``), vote:
a lock guards the attribute when it is held at a strict majority of
the non-``__init__`` accesses AND at >= 2 of them (one guarded site
is an accident; two is a convention). A class-body ``_lock_guards``
declaration (the H3 convention) is AUTHORITATIVE when present — no
vote, the guard is ``self._lock``, and the declaration wins even if
the majority disagrees, because a human wrote it down. Construction
paths (``__init__`` and friends) never vote and are never flagged:
before the object escapes its constructor there is no second thread.

**H17 — unguarded access.** A read/write/mutation of an inferred-
guarded attribute, from a function at least two threads may execute,
without the guarding lock held. The witness names both threads (the
spawn root's label and chain, plus the implicit main thread), the
lock identity, and the vote that made the attribute guarded. Plain
WRITES to a ``_lock_guards``-declared attribute inside the declaring
class are H3's beat (the per-file rule already flags them) — H17
skips those so one decision never needs two suppressions.

**H18 — unsafe publication.** A mutable local (list/dict/set/deque
binding) handed across a thread boundary — as a ``Thread``/``submit``
argument or captured by a nested def that becomes the spawn target —
then mutated on BOTH sides with no lock common to all the mutation
sites. Each side's mutation lines are named; "no common lock" is the
evidence, so adding ANY shared lock (or handing over an immutable
snapshot) clears it.

**H19 — atomicity split.** A check of a guarded attribute (a read in
an ``if``/``while`` test) under the guard, whose lock scope ends
before a later write/mutation of the same attribute under a SEPARATE
hold of the same guard, in a function >= 2 threads may execute. Both
holds are correct in isolation — H17 sees nothing — but the decision
made under the first hold is stale by the second: the classic TOCTOU
on ``self._closed`` / queue-depth patterns. The region identity that
tells two holds of one lock apart is scanned per-function by
``threads.py`` (``with`` holds keyed by their opening line;
``acquire()`` regions by the acquire line).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from sparkdl_tpu.analysis.callgraph import CallGraph
from sparkdl_tpu.analysis.findings import Finding
from sparkdl_tpu.analysis.threads import (
    AccessEvent,
    ThreadFacts,
    thread_topology,
)

#: construction/serialization paths never vote and are never flagged:
#: no second thread can hold the object yet (mirrors H3's exemption)
_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__",
                   "__setstate__", "__getstate__", "__del__",
                   "__repr__"}

#: inference thresholds: a lock guards an attr when held at >= 2
#: accesses AND a strict majority — one guarded site is an accident
_MIN_GUARDED_SITES = 2


def _short(key: str) -> str:
    mod, _, qual = key.partition("::")
    mod = mod[len("sparkdl_tpu."):] if mod.startswith("sparkdl_tpu.") \
        else mod
    return f"{mod}:{qual}" if qual else mod


# ---------------------------------------------------------------------------
# guarded-by inference


@dataclass
class GuardInfo:
    """Why an attribute is considered lock-guarded."""

    lock: str                   # canonical lock id
    declared: bool              # _lock_guards said so (authoritative)
    guarded: int = 0            # majority vote: sites with the lock
    total: int = 0              # ... out of this many accesses

    def evidence(self) -> str:
        if self.declared:
            return "declared by `_lock_guards`"
        return (f"majority evidence: {_short(self.lock)} held at "
                f"{self.guarded} of {self.total} accesses")


class GuardModel:
    """guarded-by facts for one CallGraph: ``(module::Class, attr)``
    -> :class:`GuardInfo`, plus the per-function access inventory the
    rules iterate (exempt methods already dropped)."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.guards: Dict[Tuple[str, str], GuardInfo] = {}
        #: fn key -> (class key, non-exempt accesses) for methods
        self.method_accesses: Dict[
            str, Tuple[str, List[AccessEvent]]] = {}
        self._build()

    def _build(self) -> None:
        votes: Dict[Tuple[str, str], Dict[str, int]] = {}
        totals: Dict[Tuple[str, str], int] = {}
        for m in self.graph.modules.values():
            for key, tf in getattr(m, "threads", {}).items():
                f = self.graph.functions.get(key)
                if f is None:
                    continue
                parts = f.qualname.split(".")
                if len(parts) < 2 or parts[0] not in m.classes:
                    continue
                if parts[1] in _EXEMPT_METHODS:
                    continue
                ck = f"{m.module}::{parts[0]}"
                self.method_accesses[key] = (ck, tf.accesses)
                for a in tf.accesses:
                    slot = (ck, a.attr)
                    totals[slot] = totals.get(slot, 0) + 1
                    table = votes.setdefault(slot, {})
                    for lock in a.held:
                        table[lock] = table.get(lock, 0) + 1
        # the vote
        for slot, total in totals.items():
            table = votes.get(slot, {})
            best = max(table, key=lambda lk: (table[lk], lk)) \
                if table else None
            if best is not None and \
                    table[best] >= _MIN_GUARDED_SITES and \
                    table[best] * 2 > total:
                self.guards[slot] = GuardInfo(
                    best, False, table[best], total)
        # _lock_guards declarations override the vote
        for m in self.graph.modules.values():
            for cls, attrs in getattr(m, "class_guards", {}).items():
                ck = f"{m.module}::{cls}"
                lock = f"{m.module}::{cls}._lock"
                for attr in attrs:
                    slot = (ck, attr)
                    have = self.guards.get(slot)
                    self.guards[slot] = GuardInfo(
                        lock, True,
                        have.guarded if have and have.lock == lock
                        else 0,
                        have.total if have else 0)

    #: declared slots, for "H3 owns plain writes" coordination
    def is_declared(self, ck: str, attr: str) -> bool:
        gi = self.guards.get((ck, attr))
        return gi is not None and gi.declared


def _guard_model(graph: CallGraph) -> GuardModel:
    state = getattr(graph, "_sparkdl_guard_model", None)
    if state is None:
        state = GuardModel(graph)
        graph._sparkdl_guard_model = state
    return state


def _all_threads(graph: CallGraph) -> Dict[str, ThreadFacts]:
    out: Dict[str, ThreadFacts] = {}
    for m in graph.modules.values():
        out.update(getattr(m, "threads", {}) or {})
    return out


# ---------------------------------------------------------------------------
# H17 — unguarded access to a guarded attribute


_VERB = {"read": "read", "write": "written", "mut": "mutated",
         "check": "read (in a branch test)"}


def check_h17(graph: CallGraph) -> List[Finding]:
    topo = thread_topology(graph)
    model = _guard_model(graph)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for key, (ck, accesses) in sorted(model.method_accesses.items()):
        if not topo.is_concurrent(key):
            continue
        f = graph.functions[key]
        for a in accesses:
            gi = model.guards.get((ck, a.attr))
            if gi is None or gi.lock in a.held:
                continue
            if a.kind == "write" and gi.declared:
                continue    # the per-file H3 owns plain writes
            marker = (f.path, a.line, a.attr)
            if marker in seen:
                continue
            seen.add(marker)
            findings.append(Finding(
                rule="H17", path=f.path, line=a.line, col=0,
                qualname=f.qualname,
                message=(
                    f"`self.{a.attr}` {_VERB[a.kind]} without holding "
                    f"{_short(gi.lock)}: the attribute is lock-guarded "
                    f"({gi.evidence()}) and {_short(key)} is reachable "
                    f"by {topo.witness(key)} — hold the lock around "
                    "this access or suppress with `# sparkdl-lint: "
                    "allow[H17] -- <why unguarded is safe here>`")))
    findings.sort(key=lambda x: (x.path, x.line))
    return findings


# ---------------------------------------------------------------------------
# H18 — unsafe publication of mutable state


def _lock_token(lock: str) -> str:
    """Function-scoped lock ids (``module::qual.<name>`` — a local or
    parameter named like a lock) compare by their bare ``<name>``: the
    same lexical lock seen from a spawner and from the nested def it
    hands work to carries two qualnames but one name. An over-
    approximation in the conservative direction — a false "common
    lock" only mutes a finding."""
    mod, sep, qual = lock.partition("::")
    if sep and qual.endswith(">") and "<" in qual:
        return qual[qual.rindex("<"):]
    return lock


def _common_lock(*held_sets: Tuple[str, ...]) -> Optional[str]:
    """A lock held at EVERY site, or None."""
    if not held_sets:
        return None
    common = {_lock_token(lk) for lk in held_sets[0]}
    for held in held_sets[1:]:
        common &= {_lock_token(lk) for lk in held}
    return sorted(common)[0] if common else None


def check_h18(graph: CallGraph) -> List[Finding]:
    topo = thread_topology(graph)
    tfacts = _all_threads(graph)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for key in sorted(tfacts):
        tf = tfacts[key]
        f = graph.functions.get(key)
        if f is None:
            continue
        for sp in tf.spawns:
            targets = topo._spawn_targets(f, sp)
            for tkey in targets:
                ttf = tfacts.get(tkey)
                if ttf is None:
                    continue
                _h18_args(findings, seen, graph, f, tf, sp, tkey, ttf)
                _h18_capture(findings, seen, graph, f, tf, sp, tkey,
                             ttf, key)
    findings.sort(key=lambda x: (x.path, x.line))
    return findings


def _h18_args(findings, seen, graph, f, tf, sp, tkey, ttf):
    """The argument hand-off shape: ``Thread(target=w, args=(buf,))``
    / ``pool.submit(w, buf)`` where ``buf`` is a mutable local the
    spawner keeps mutating and the target mutates its mapped param."""
    for idx, ref in enumerate(sp.args):
        if not ref or "." in ref or ref not in tf.mutable_locals:
            continue
        ours = [(ln, held) for n, ln, held in tf.local_muts
                if n == ref and ln > sp.line]
        if not ours:
            continue
        if idx >= len(ttf.params):
            continue
        param = ttf.params[idx]
        theirs = [(ln, held) for n, ln, held in ttf.local_muts
                  if n == param]
        if not theirs:
            continue
        if _common_lock(*[h for _, h in ours],
                        *[h for _, h in theirs]) is not None:
            continue
        marker = (f.path, sp.line, ref)
        if marker in seen:
            continue
        seen.add(marker)
        our_lines = ", ".join(str(ln) for ln, _ in ours[:3])
        their_lines = ", ".join(str(ln) for ln, _ in theirs[:3])
        findings.append(Finding(
            rule="H18", path=f.path, line=sp.line, col=0,
            qualname=f.qualname,
            message=(
                f"mutable local `{ref}` handed to `{sp.display}` "
                f"({_h18_kind(sp)}) and mutated on both sides with no "
                f"common lock: {_short(f.key)} keeps mutating it "
                f"(line {our_lines}) while {_short(tkey)} mutates its "
                f"`{param}` parameter (line {their_lines}) — guard "
                "both sides with one lock, hand over an immutable "
                "snapshot, or suppress with `# sparkdl-lint: "
                "allow[H18] -- <why the sharing is safe>`")))


def _h18_capture(findings, seen, graph, f, tf, sp, tkey, ttf, key):
    """The closure-capture shape: the spawn target is a def nested in
    the spawner, mutating a mutable local it captured (not a param,
    not rebound locally) that the spawner mutates too."""
    if not tkey.startswith(key + "."):
        return
    for n, ln, held in ttf.local_muts:
        if n in ttf.params or n in ttf.locals_bound:
            continue
        if n not in tf.mutable_locals:
            continue
        ours = [(ln2, h) for n2, ln2, h in tf.local_muts if n2 == n]
        if not ours:
            continue
        theirs = [(ln2, h) for n2, ln2, h in ttf.local_muts
                  if n2 == n]
        if _common_lock(*[h for _, h in ours],
                        *[h for _, h in theirs]) is not None:
            continue
        marker = (f.path, sp.line, n)
        if marker in seen:
            continue
        seen.add(marker)
        findings.append(Finding(
            rule="H18", path=f.path, line=sp.line, col=0,
            qualname=f.qualname,
            message=(
                f"mutable local `{n}` captured by `{sp.display}` "
                f"({_h18_kind(sp)}) and mutated on both sides with no "
                f"common lock: {_short(key)} mutates it at line "
                f"{ours[0][0]} while the captured {_short(tkey)} "
                f"mutates it at line {theirs[0][0]} — guard both "
                "sides with one lock or suppress with "
                "`# sparkdl-lint: allow[H18] -- <why>`")))


def _h18_kind(sp) -> str:
    return {"thread": "a thread target", "timer": "a timer callback",
            "pool": "an executor task",
            "callback": "a future done-callback",
            "http": "a per-request handler",
            "signal": "a signal handler"}.get(sp.kind, sp.kind)


# ---------------------------------------------------------------------------
# H19 — atomicity split (check-then-act across separate holds)


def check_h19(graph: CallGraph) -> List[Finding]:
    topo = thread_topology(graph)
    model = _guard_model(graph)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for key, (ck, accesses) in sorted(model.method_accesses.items()):
        if not topo.is_concurrent(key):
            continue
        f = graph.functions[key]
        by_attr: Dict[str, List[AccessEvent]] = {}
        for a in accesses:
            by_attr.setdefault(a.attr, []).append(a)
        for attr, events in by_attr.items():
            gi = model.guards.get((ck, attr))
            if gi is None:
                continue
            checks = [(a, dict(a.regions).get(gi.lock))
                      for a in events if a.kind == "check"
                      and gi.lock in a.held]
            acts = [(a, dict(a.regions).get(gi.lock))
                    for a in events if a.kind in ("write", "mut")
                    and gi.lock in a.held]
            # double-checked locking is the REMEDY, not the hazard: an
            # act whose own hold re-checks the attribute first (a
            # check in the same region, at or before the act) made the
            # stale first check harmless — exempt those regions
            rechecked: Dict[Tuple[str, int], int] = {}
            for a, region in checks:
                if region is not None:
                    slot = (a.attr, region)
                    rechecked[slot] = min(
                        rechecked.get(slot, a.line), a.line)
            for chk, chk_region in checks:
                for act, act_region in acts:
                    if act.line <= chk.line or \
                            act_region == chk_region:
                        continue
                    recheck = rechecked.get((act.attr, act_region))
                    if recheck is not None and recheck <= act.line:
                        continue
                    marker = (f.path, act.line, attr)
                    if marker in seen:
                        continue
                    seen.add(marker)
                    findings.append(Finding(
                        rule="H19", path=f.path, line=act.line, col=0,
                        qualname=f.qualname,
                        message=(
                            f"check-then-act split on `self.{attr}`: "
                            f"checked under {_short(gi.lock)} at line "
                            f"{chk.line} but acted on under a "
                            f"SEPARATE hold at line {act.line} — the "
                            "lock was dropped in between, so the "
                            "checked condition can be stale (TOCTOU); "
                            f"{_short(key)} is reachable by "
                            f"{topo.witness(key)} — widen one hold "
                            "over both, re-check under the second, or "
                            "suppress with `# sparkdl-lint: "
                            "allow[H19] -- <why staleness is safe>`")))
                    break
    findings.sort(key=lambda x: (x.path, x.line))
    return findings
