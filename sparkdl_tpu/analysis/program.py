"""The whole-program rules: H7 lock-order cycles, H8 blocking under a
lock.

Both run over the :class:`~sparkdl_tpu.analysis.callgraph.CallGraph`
built from EVERY analyzed module at once — the failure modes they hunt
(PR 2's collective-enqueue deadlock; a dispatcher lock held into a
device drain) never live in one file.

**H7 — lock-order-cycle.** Build the *acquired-while-holding* digraph:
an edge ``A → B`` means some function acquires lock ``B`` (directly,
or through any resolved call chain) while already holding ``A``. Any
cycle in that graph is a deadlock schedule: thread 1 runs one edge's
witness, thread 2 the other's, each stops inside the other's hold.
The finding prints the full witness path module-by-module — which
function held what at which line, and through which call chain the
second lock is reached — because a deadlock report an operator cannot
retrace is a number, not a diagnosis. This is exactly the shape of the
PR-2 production deadlock (two trials enqueuing collective programs
onto per-device FIFO queues in opposite orders), reconstructed as a
fixture in ``tests/test_callgraph.py`` and required caught.

**H8 — blocking-call-under-lock.** A lock held across a blocking
operation — device sync (`timed_device_get` / `.block_until_ready()`),
`Condition.wait`, `queue.get`, `time.sleep`, file/socket I/O, a
thread join — serializes every other thread that touches that lock
behind the slow operation, and is one late `notify` away from a hang.
Flagged directly and transitively (a resolved callee that may block,
with the chain printed). The dispatcher's intentional coalescing
``Condition.wait`` (serve/batching.py) is allowlisted — the batching
window IS the product there; everything else suppresses inline with a
reason, per the H1–H6 grammar.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from sparkdl_tpu.analysis.callgraph import CallGraph
from sparkdl_tpu.analysis.dataflow import check_h14, check_h15, check_h16
from sparkdl_tpu.analysis.effects import check_h10, check_h11
from sparkdl_tpu.analysis.findings import Finding
from sparkdl_tpu.analysis.locks import FunctionFacts
from sparkdl_tpu.analysis.races import check_h17, check_h18, check_h19


def short_lock(lock: str) -> str:
    mod, _, attr = lock.partition("::")
    mod = mod[len("sparkdl_tpu."):] if mod.startswith("sparkdl_tpu.") \
        else mod
    return f"{mod}:{attr}" if attr else mod


# ---------------------------------------------------------------------------
# H7 — lock-order cycles


class _Edge:
    """One acquired-while-holding edge with its human witness."""

    __slots__ = ("held", "acquired", "path", "line", "qual", "chain")

    def __init__(self, held: str, acquired: str, path: str, line: int,
                 qual: str, chain: str):
        self.held = held
        self.acquired = acquired
        self.path = path
        self.line = line
        self.qual = qual
        self.chain = chain          # "" for a direct acquire


def _collect_edges(graph: CallGraph) -> List[_Edge]:
    edges: List[_Edge] = []
    for key, f in graph.functions.items():
        for acq in f.acquires:
            for held in acq.held:
                if held == acq.lock:
                    continue
                edges.append(_Edge(held, acq.lock, f.path, acq.line,
                                   graph.short(key), ""))
        for call in f.calls:
            if not call.held:
                continue
            target = graph.resolve(f, call)
            if target is None or target == key:
                continue
            for lock, chain in graph.may_acquire(target).items():
                # held == lock is re-entry through a call chain — a
                # plain Lock self-deadlocks; it becomes a 1-cycle
                for held in call.held:
                    edges.append(_Edge(
                        held, lock, f.path, call.line,
                        graph.short(key), " -> ".join(chain)))
    return edges


def _find_cycles(adj: Dict[str, Set[str]]) -> List[Tuple[str, ...]]:
    """Simple cycles, deduped by their lock set, shortest first. DFS
    with a bounded path (lock graphs here are tiny)."""
    cycles: List[Tuple[str, ...]] = []
    seen_sets: Set[frozenset] = set()
    nodes = sorted(adj)

    def dfs(start: str, node: str, path: List[str]):
        if len(path) > 6:
            return
        for nxt in sorted(adj.get(node, ())):
            if nxt == start and len(path) >= 1:
                key = frozenset(path)
                if key not in seen_sets:
                    # a self-loop is a real finding only as re-entry
                    seen_sets.add(key)
                    cycles.append(tuple(path))
            elif nxt not in path and nxt > start:
                # canonical start = smallest node keeps dedup cheap
                dfs(start, nxt, path + [nxt])

    for n in nodes:
        # self loops (re-entry deadlocks)
        if n in adj.get(n, ()):
            key = frozenset((n,))
            if key not in seen_sets:
                seen_sets.add(key)
                cycles.append((n,))
        dfs(n, n, [n])
    return cycles


def check_h7(graph: CallGraph) -> List[Finding]:
    edges = _collect_edges(graph)
    adj: Dict[str, Set[str]] = {}
    by_pair: Dict[Tuple[str, str], _Edge] = {}
    for e in edges:
        adj.setdefault(e.held, set()).add(e.acquired)
        by_pair.setdefault((e.held, e.acquired), e)
    findings: List[Finding] = []
    for cycle in _find_cycles(adj):
        steps = [(cycle[i], cycle[(i + 1) % len(cycle)])
                 for i in range(len(cycle))]
        witnesses = [by_pair[s] for s in steps if s in by_pair]
        if not witnesses:
            continue
        first = witnesses[0]
        lines = []
        for (held, acquired), w in zip(steps, witnesses):
            via = f" via {w.chain}" if w.chain else ""
            lines.append(
                f"[{w.qual} at {w.path}:{w.line}] holds "
                f"{short_lock(held)} and acquires "
                f"{short_lock(acquired)}{via}")
        if len(cycle) == 1:
            head = (f"lock re-entry deadlock on "
                    f"{short_lock(cycle[0])} (a plain Lock is not "
                    "reentrant)")
        else:
            ring = " -> ".join(short_lock(c) for c in cycle)
            head = (f"lock-order cycle {ring} -> "
                    f"{short_lock(cycle[0])}: two threads running "
                    "these witnesses in parallel deadlock (the PR-2 "
                    "collective-enqueue shape)")
        findings.append(Finding(
            rule="H7", path=first.path, line=first.line, col=0,
            qualname=first.qual.partition(":")[2] or first.qual,
            message=(head + "; witness: " + "; ".join(lines)
                     + " (suppress: `# sparkdl-lint: allow[H7] -- "
                       "<why this order is safe>`)")))
    return findings


# ---------------------------------------------------------------------------
# H8 — blocking calls while holding a lock


def check_h8(graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for key, f in graph.functions.items():
        for b in f.blocks:
            if not b.held:
                continue
            marker = (f.path, b.line, b.what)
            if marker in seen:
                continue
            seen.add(marker)
            findings.append(Finding(
                rule="H8", path=f.path, line=b.line, col=0,
                qualname=f.qualname,
                message=(
                    f"{b.what} while holding "
                    f"{_held_str(b.held)}: every thread contending "
                    "that lock now waits out this operation too — "
                    "move the blocking work outside the lock scope, "
                    "or suppress with `# sparkdl-lint: allow[H8] -- "
                    "<why the hold is the point>`")))
        for call in f.calls:
            if not call.held:
                continue
            target = graph.resolve(f, call)
            if target is None or target == key:
                continue
            hit = graph.may_block(target)
            if hit is None:
                continue
            chain, op = hit
            marker = (f.path, call.line, chain)
            if marker in seen:
                continue
            seen.add(marker)
            findings.append(Finding(
                rule="H8", path=f.path, line=call.line, col=0,
                qualname=f.qualname,
                message=(
                    f"`{call.display}(...)` may block "
                    f"(transitively: {chain} reaches {op}) while "
                    f"holding {_held_str(call.held)} — the lock "
                    "serializes every contender behind that stall; "
                    "narrow the lock scope or suppress with "
                    "`# sparkdl-lint: allow[H8] -- <why>`")))
    findings.sort(key=lambda x: (x.path, x.line))
    return findings


def _held_str(held: Tuple[str, ...]) -> str:
    return ", ".join(short_lock(h) for h in held)


#: the program-rule registry (walker.py runs these over the one
#: CallGraph it builds per invocation; H9 lives in contracts.py
#: because it needs the docs tree, not the call graph; H10/H11 live
#: in effects.py with the effect closure they consume; H14–H16 live
#: in dataflow.py with the device-dataflow replay + hot-path
#: classification they run on; H17–H19 live in races.py with the
#: thread topology + guarded-by model they share)
PROGRAM_RULES = {
    "H7": check_h7,
    "H8": check_h8,
    "H10": check_h10,
    "H11": check_h11,
    "H14": check_h14,
    "H15": check_h15,
    "H16": check_h16,
    "H17": check_h17,
    "H18": check_h18,
    "H19": check_h19,
}
