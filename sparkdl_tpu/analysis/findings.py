"""Finding record + ``path:line`` formatter for sparkdl-lint."""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Iterable, List


@dataclass
class Finding:
    """One rule hit at one source location.

    ``suppressed`` findings stay in the result set (the meta-test and
    ``--show-suppressed`` render them) but don't fail the CLI;
    ``suppression`` records WHY — the inline justification text or the
    matching allowlist entry — so a suppression is never silent.
    """

    rule: str                 # "H1".."H4" (or "PARSE" for broken files)
    path: str                 # as given to the walker (relative-friendly)
    line: int                 # 1-indexed
    col: int                  # 0-indexed, ast convention
    message: str
    qualname: str = ""        # dotted Class.method containing the hit
    suppressed: bool = field(default=False)
    suppression: str = field(default="")

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}"

    def render(self) -> str:
        where = f" [{self.qualname}]" if self.qualname else ""
        head = f"{self.location()}: {self.rule}{where} {self.message}"
        if self.suppressed:
            head += f"  (suppressed: {self.suppression})"
        return head


def format_findings(findings: Iterable[Finding],
                    show_suppressed: bool = False,
                    fmt: str = "text") -> str:
    """Render findings for the CLI. ``text`` gives one ``path:line:col``
    line per finding (editor/CI friendly); ``json`` gives a list of
    dicts plus a summary object."""
    findings = list(findings)  # may be a generator; iterated repeatedly
    shown: List[Finding] = [
        f for f in findings if show_suppressed or not f.suppressed]
    if fmt == "json":
        unsuppressed = [f for f in findings if not f.suppressed]
        return json.dumps({
            "findings": [asdict(f) for f in shown],
            "unsuppressed": len(unsuppressed),
            "suppressed": len([f for f in findings if f.suppressed]),
        }, indent=2)
    if fmt != "text":
        raise ValueError(f"unknown format {fmt!r} (use 'text' or 'json')")
    return "\n".join(f.render() for f in shown)
