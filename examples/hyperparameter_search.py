"""Parallel hyperparameter search with CrossValidator.

Reference: ``KerasImageFileEstimator`` + ``pyspark.ml.tuning
.CrossValidator`` (one Spark task per ParamMap); here trials run
concurrently on the estimator's thread pool, each a jax/optax train
loop, optionally data-parallel over the device mesh.

Run:  KERAS_BACKEND=jax python examples/hyperparameter_search.py
"""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")  # must precede keras import

import tempfile

import numpy as np
from PIL import Image

import sparkdl_tpu
from sparkdl_tpu.data import DataFrame
from sparkdl_tpu.estimators import ClassificationEvaluator


def main():
    import keras
    keras.utils.set_random_seed(0)
    size = 16

    model = keras.Sequential([
        keras.layers.Input((size, size, 3)),
        keras.layers.Flatten(),
        keras.layers.Dense(2, activation="softmax"),
    ])
    model_file = os.path.join(tempfile.mkdtemp(), "model.keras")
    model.save(model_file)

    d = tempfile.mkdtemp(prefix="sparkdl_tpu_hpo_")
    rng = np.random.default_rng(2)
    rows = []
    for i in range(24):
        label = i % 2
        base = 60 if label == 0 else 190
        arr = np.clip(rng.normal(base, 25, (size, size, 3)), 0,
                      255).astype(np.uint8)
        p = os.path.join(d, f"h{i}.png")
        Image.fromarray(arr, "RGB").save(p)
        rows.append({"uri": p, "label": label})
    df = DataFrame.from_pylist(rows, num_partitions=3)

    def loader(uri):
        return np.asarray(Image.open(uri).convert("RGB"),
                          np.float32) / 255.0

    est = sparkdl_tpu.KerasImageFileEstimator(
        inputCol="uri", outputCol="prediction", labelCol="label",
        modelFile=model_file, imageLoader=loader,
        kerasOptimizer="adam", kerasLoss="categorical_crossentropy",
        parallelism=2)

    grid = (sparkdl_tpu.ParamGridBuilder()
            .addGrid(est.getParam("kerasFitParams"), [
                {"epochs": 2, "batch_size": 8, "learning_rate": 1e-3},
                {"epochs": 8, "batch_size": 8, "learning_rate": 1e-2},
            ])
            .build())

    cv = sparkdl_tpu.CrossValidator(
        estimator=est, estimatorParamMaps=grid,
        evaluator=ClassificationEvaluator(predictionCol="prediction",
                                          labelCol="label"),
        numFolds=2)
    cv_model = cv.fit(df)
    print("fold-averaged accuracies per config:",
          [round(m, 3) for m in cv_model.avgMetrics])
    preds = cv_model.transform(df).tensor("prediction")
    print("best model prediction matrix:", preds.shape)


if __name__ == "__main__":
    main()
