"""Transfer learning: featurize with a pretrained CNN, train a classifier.

The reference README's headline example (DeepImageFeaturizer + MLlib
LogisticRegression). Synthesizes a small labeled image set so it runs
anywhere; swap `DATA_DIR`/`MODEL` for real data (e.g.
MODEL="InceptionV3" after `import_named_model("InceptionV3")` has cached
pretrained weights).

Run:  python examples/transfer_learning.py
"""

import os
import tempfile

import numpy as np
from PIL import Image

import sparkdl_tpu
from sparkdl_tpu.data import DataFrame
from sparkdl_tpu.estimators import ClassificationEvaluator

MODEL = os.environ.get("SPARKDL_TPU_EXAMPLE_MODEL", "TestNet")


def synthesize_dataset(n=24):
    """Images whose brightness encodes the class."""
    d = tempfile.mkdtemp(prefix="sparkdl_tpu_tl_")
    rng = np.random.default_rng(0)
    rows = []
    for i in range(n):
        label = i % 2
        base = 60 if label == 0 else 190
        arr = np.clip(rng.normal(base, 30, (64, 64, 3)), 0,
                      255).astype(np.uint8)
        path = os.path.join(d, f"img_{i}.png")
        Image.fromarray(arr, "RGB").save(path)
        rows.append({"filePath": path, "label": label})
    return d, rows


def main():
    data_dir, rows = synthesize_dataset()

    # 1. read images (decode on host threads, lazily per partition)
    df = sparkdl_tpu.readImages(data_dir, numPartitions=4)

    # 2. attach labels: broadcast hash join on the file path (the small
    # label table ships into the streamed probe, Spark-style)
    labels_df = DataFrame.from_pylist(rows, num_partitions=1)
    labeled = df.join(labels_df, on="filePath")

    # 3. featurizer + logistic regression as ONE pipeline
    pipeline = sparkdl_tpu.Pipeline(stages=[
        sparkdl_tpu.DeepImageFeaturizer(modelName=MODEL, inputCol="image",
                                        outputCol="features"),
        sparkdl_tpu.LogisticRegression(featuresCol="features",
                                       labelCol="label", maxIter=120,
                                       learningRate=0.2),
    ])
    model = pipeline.fit(labeled)

    # 4. score
    scored = model.transform(labeled)
    acc = ClassificationEvaluator(predictionCol="prediction",
                                  labelCol="label").evaluate(scored)
    print(f"model={MODEL} train accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
