"""Bounded-memory model selection: disk-spill CV + streaming head.

The reference's tuning flow collected the dataset to the driver
(``KerasImageFileEstimator`` docs call it the scalability cliff). This
build removes the cliff end to end:

* ``CrossValidator(cacheDir=...)`` materializes the upstream plan ONCE
  into an Arrow IPC disk spill; fold membership is computed per
  partition batch as a plan stage — no collected table, no global mask.
* ``LogisticRegression(streaming=True)`` trains from the partition
  stream, one partition + one minibatch in memory.
* Evaluators reduce batches into exact sufficient statistics
  (confusion counts, rank sums) — the scored table is never held.

To PROVE the property, this example monkeypatches ``DataFrame.collect``
to raise during the fit: the whole selection loop runs anyway.

Run:  python examples/out_of_core_tuning.py
"""

import os
import tempfile

import numpy as np
import pyarrow as pa

import sparkdl_tpu
from sparkdl_tpu.data import DataFrame
from sparkdl_tpu.data.tensors import append_tensor_column
from sparkdl_tpu.estimators import ClassificationEvaluator


class StreamingAccuracy(ClassificationEvaluator):
    """ClassificationEvaluator already streams — subclass only to make
    the example's intent explicit in its name."""


def main():
    rng = np.random.default_rng(5)
    n, d, parts = 400, 16, 8
    y = rng.integers(0, 2, n)
    X = rng.normal(0, 1, (n, d)).astype(np.float32) + 2.5 * y[:, None]
    batches = []
    for lo in range(0, n, n // parts):
        hi = lo + n // parts
        b = pa.RecordBatch.from_pylist(
            [{"label": int(v)} for v in y[lo:hi]])
        batches.append(append_tensor_column(b, "features", X[lo:hi]))
    df = DataFrame.from_batches(batches)

    lr = sparkdl_tpu.LogisticRegression(
        maxIter=25, learningRate=0.2, batchSize=64,
        streaming=True, numClasses=2)
    cv = sparkdl_tpu.CrossValidator(
        estimator=lr,
        estimatorParamMaps=[{lr.regParam: 0.0}, {lr.regParam: 20.0}],
        evaluator=StreamingAccuracy(predictionCol="prediction"),
        numFolds=3, seed=1,
        cacheDir=tempfile.mkdtemp(prefix="sparkdl_cv_spill_"))

    # the proof: nothing in the selection loop may collect a table
    orig = DataFrame.collect

    def refuse(self):
        raise AssertionError("bounded-memory violated: collect() ran")

    DataFrame.collect = refuse
    try:
        model = cv.fit(df)
    finally:
        DataFrame.collect = orig

    scored = model.transform(df)
    acc = StreamingAccuracy(predictionCol="prediction").evaluate(scored)
    print(f"avgMetrics per grid point: "
          f"{[round(m, 3) for m in model.avgMetrics]}")
    print(f"best model full-frame accuracy: {acc:.3f} "
          f"(fit + evaluation ran with DataFrame.collect disabled)")
    assert acc >= 0.9


if __name__ == "__main__":
    main()
