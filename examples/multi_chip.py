"""Multi-chip inference and data-parallel fine-tuning over a mesh.

What the reference scaled with Spark executors, this build scales with
a ``jax.sharding.Mesh``: inference batches split over the ``data`` axis
(useMesh=True on any transformer), training with XLA-inserted gradient
all-reduce over ICI, checkpointed with orbax.

Run on CPU with 8 simulated devices:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/multi_chip.py
"""

import tempfile

import numpy as np
from PIL import Image

import jax
import jax.numpy as jnp
import optax

import sparkdl_tpu
from sparkdl_tpu.models.testnet import TestNet
from sparkdl_tpu.models.zoo import getKerasApplicationModel
from sparkdl_tpu.parallel import (
    checkpoint,
    create_train_state,
    host_shard_dataframe,
    initialize,
    make_mesh,
    make_train_step,
    shard_train_step,
)


def main():
    initialize()   # no-op single-host; joins the pod on multi-host jobs
    print(f"devices: {jax.device_count()} "
          f"({jax.process_count()} process(es))")

    # --- sharded inference through the pipeline surface ---------------
    d = tempfile.mkdtemp(prefix="sparkdl_tpu_mesh_")
    rng = np.random.default_rng(3)
    for i in range(16):
        Image.fromarray(rng.integers(0, 255, (32, 32, 3), dtype=np.uint8),
                        "RGB").save(f"{d}/m{i}.png")
    df = sparkdl_tpu.readImages(d, numPartitions=4)
    df = host_shard_dataframe(df)  # this host's partitions (multi-host)

    feat = sparkdl_tpu.DeepImageFeaturizer(
        modelName="TestNet", inputCol="image", outputCol="features",
        useMesh=True)     # batch split over every local chip
    features = feat.transform(df).tensor("features")
    print("sharded featurization:", features.shape)

    # --- data-parallel fine-tune with orbax checkpoints ---------------
    mesh = make_mesh()
    spec = getKerasApplicationModel("TestNet")
    module = TestNet()
    variables = module.init(
        jax.random.PRNGKey(0),
        spec.preprocess(jnp.zeros((1, 32, 32, 3), jnp.uint8)))
    state = create_train_state(module, variables, optax.sgd(1e-2, 0.9))
    step = make_train_step(module, spec.preprocess,
                           num_classes=spec.num_classes)
    jitted, state = shard_train_step(step, mesh, state)

    per_chip = 4
    n = per_chip * mesh.shape["data"]
    batch = {
        "image": jnp.asarray(rng.integers(0, 255, (n, 32, 32, 3),
                                          dtype=np.uint8)),
        "label": jnp.asarray(rng.integers(0, 10, (n,), dtype=np.int32)),
    }
    ckpt_dir = tempfile.mkdtemp(prefix="sparkdl_tpu_ckpt_")
    for i in range(3):
        state, metrics = jitted(state, batch)
        print(f"step {i}: loss={float(metrics['loss']):.4f}")
    checkpoint.save_checkpoint(ckpt_dir, state, step=3)
    print("checkpointed at", ckpt_dir, "(resume with restore_checkpoint)")


if __name__ == "__main__":
    main()
