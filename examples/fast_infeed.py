"""The three infeed strategies, fastest host work first.

The north-star bottleneck is host-side decode/resize + host->device
transfer (BASELINE.md), so the framework offers three ways to feed a
model, trading host CPU work for device work:

1. classic     readImages -> host resize/pack (C++ shim) -> model
2. fused host  readImagesPacked: JPEG decode + resize + NHWC pack in
               ONE native call per partition -> TensorTransformer
3. device      readImages -> pack at native size (zero-copy Arrow
               views) -> deviceResizeFrom: bilinear resize fused INTO
               the model's XLA program (Pallas kernel on real TPU) —
               host CPUs only decode
4. yuv420      readImagesPacked(packedFormat="yuv420"): ship planar
               YCbCr 4:2:0 at 1.5 B/px — HALF the link bytes — with
               chroma upsample + BT.601 reconstruction + resize fused
               on-device (the bench headline's shape; standard 4:2:0
               JPEGs stream out of libjpeg raw, skipping host chroma
               work entirely)

The packed readers (2 and 4) additionally prescale in the DCT domain
by default (scaledDecode=True: libjpeg decodes at the largest
power-of-two shrink still covering the target — PIL-draft semantics,
most IDCT work skipped; scaledDecode=False restores full-res-decode
pixels).

Run on CPU:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/fast_infeed.py
"""

import tempfile

import numpy as np
from PIL import Image

import sparkdl_tpu
from sparkdl_tpu.data.engine import LocalEngine
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.models.zoo import getModelFunction
from sparkdl_tpu.utils import StageMetrics


def make_images(n=12, hw=(48, 64)):
    d = tempfile.mkdtemp(prefix="sparkdl_tpu_infeed_")
    rng = np.random.default_rng(7)
    for i in range(n):
        arr = rng.integers(0, 255, (*hw, 3), dtype=np.uint8)
        Image.fromarray(arr, "RGB").save(f"{d}/img_{i}.jpg", quality=92)
    return d


def main():
    d = make_images()
    metrics = StageMetrics()
    engine = LocalEngine(stage_metrics=metrics)

    # 1. classic: host resize inside packImageBatch
    df = sparkdl_tpu.readImages(d, numPartitions=3, engine=engine)
    classic = sparkdl_tpu.DeepImageFeaturizer(
        modelName="TestNet", inputCol="image", outputCol="f"
    ).transform(df).tensor("f")

    # 2. fused host reader: decode+resize+pack in one native call,
    #    then a tensor column straight into the model
    packed = imageIO.readImagesPacked(d, (32, 32), numPartitions=3,
                                      engine=engine)
    fused = sparkdl_tpu.TensorTransformer(
        modelFunction=getModelFunction("TestNet", featurize=True),
        inputMapping={"image": "image"},
        outputMapping={"features": "f"},
    ).transform(packed).tensor("f")

    # 3. device resize: host only decodes; resample runs on-device,
    #    fused into the model program
    device = sparkdl_tpu.DeepImageFeaturizer(
        modelName="TestNet", inputCol="image", outputCol="f",
        deviceResizeFrom=(48, 64)
    ).transform(df).tensor("f")

    # 4. half-the-bytes 4:2:0 ship, reconstruction fused on-device
    from sparkdl_tpu.transformers.utils import deviceResizeModel, single_io
    mf420 = deviceResizeModel(getModelFunction("TestNet", featurize=True),
                              (24, 24), packedFormat="yuv420")
    in420, out420 = single_io(mf420)
    packed420 = imageIO.readImagesPacked(d, (24, 24), numPartitions=3,
                                         packedFormat="yuv420",
                                         engine=engine)
    yuv = sparkdl_tpu.TensorTransformer(
        modelFunction=mf420, inputMapping={"image": in420},
        outputMapping={out420: "f"},
    ).transform(packed420).tensor("f")

    assert classic.shape == fused.shape == device.shape == yuv.shape
    # different resamplers (host bilinear / native fused / device AA
    # bilinear) agree closely on features
    c = np.corrcoef(classic.ravel(), device.ravel())[0, 1]
    c420 = np.corrcoef(classic.ravel(), yuv.ravel())[0, 1]
    print(f"feature shape {classic.shape}; "
          f"classic-vs-device correlation {c:.4f}; "
          f"classic-vs-yuv420 {c420:.4f}")
    print("per-stage metrics (rows/sec):")
    print(metrics.report())


if __name__ == "__main__":
    main()
