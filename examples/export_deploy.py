"""Export a model as StableHLO, serve it from a fresh process, then
put it behind an online ModelServer.

The reference broadcast frozen GraphDef bytes inside Spark task closures
to every executor (SURVEY §2.5); the TPU-era deploy form is serialized
StableHLO from ``jax.export``: params baked in, computation portable,
loadable without the model's Python code. This example exports on the
"driver", then loads and serves in a NEW python process that never
imports the zoo — exactly what a worker that only has the bytes does.

The last act is the ONLINE shape (docs/SERVING.md): the same deployed
bytes behind a ``ModelServer`` — concurrent sub-batch requests from
several threads, dynamically micro-batched into the export's fixed
device batch, with the serve counters printed at the end.

Run on CPU:
  JAX_PLATFORMS=cpu python examples/export_deploy.py
"""

import os
import subprocess
import sys
import tempfile

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from sparkdl_tpu.graph.function import ModelFunction

blob = open(sys.argv[1], "rb").read()
mf = ModelFunction.deserialize(blob, name="deployed")
x = np.load(sys.argv[2])
out = mf({mf.input_names[0]: x})
np.save(sys.argv[3], np.asarray(out[mf.output_names[0]]))
print("served", x.shape, "->", np.asarray(out[mf.output_names[0]]).shape)
"""


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")

    from sparkdl_tpu.models.zoo import getModelFunction

    # "driver": build + freeze (params baked into the program)
    mf = getModelFunction("TestNet", featurize=True)
    batch = 4
    blob = mf.export(batch_size=batch)
    print(f"exported {mf.name}: {len(blob) // 1024} KiB StableHLO")

    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, (batch, 32, 32, 3), dtype=np.uint8)
    expected = np.asarray(mf({mf.input_names[0]: x})[mf.output_names[0]])

    # "worker": a fresh process with only the bytes
    with tempfile.TemporaryDirectory(prefix="sparkdl_tpu_deploy_") as d:
        blob_p = os.path.join(d, "model.stablehlo")
        x_p = os.path.join(d, "x.npy")
        out_p = os.path.join(d, "out.npy")
        open(blob_p, "wb").write(blob)
        np.save(x_p, x)

        from sparkdl_tpu.utils.hostenv import sanitized_cpu_env
        proc = subprocess.run(
            [sys.executable, "-c", WORKER, blob_p, x_p, out_p],
            env=sanitized_cpu_env(pythonpath=REPO_ROOT),
            capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(f"worker failed:\n{proc.stderr[-2000:]}")
        print(proc.stdout.strip())
        got = np.load(out_p)

    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)
    print(f"worker output matches driver oracle "
          f"(max abs diff {np.abs(got - expected).max():.2e})")

    # -- online serving: the deployed bytes behind a ModelServer ------------
    # The export is fixed-batch (batch=4); the server's micro-batcher
    # pads every dispatch to exactly that shape, so single-row requests
    # from many threads are served by the same compiled program.
    import threading
    import time

    from sparkdl_tpu.graph.function import ModelFunction
    from sparkdl_tpu.obs.compile_log import compile_log
    from sparkdl_tpu.serve import ModelServer, ServeConfig

    # ROADMAP item 4's AOT warm-start case, MEASURED from the compile
    # log (docs/OBSERVABILITY.md "Compile forensics"): the same
    # deployed bytes served cold (first request pays the jit on the
    # hot path) vs warmed (warmup() moves the one compile before
    # traffic) — the difference is what a freshly scaled worker saves.
    clog = compile_log()
    saved_override = clog._override   # restore the caller's arming
    clog.arm()

    def first_request_s(name: str, warm: bool) -> float:
        # deltas, not totals: the process-wide log survives repeated
        # in-process runs of this example (the test suite's runpy)
        base = clog.compiles_of(f"{name}.jitted")
        m = ModelFunction.deserialize(blob, name=name)
        srv = ModelServer(ServeConfig(max_wait_s=0.02))
        srv.register(name, m, batch_size=batch)
        if warm:
            srv.warmup()
        t0 = time.perf_counter()
        srv.submit({m.input_names[0]: x[:1]}).result(timeout=120)
        latency = time.perf_counter() - t0
        srv.close()
        # the compile log attributes where the jit landed: cold serves
        # compile ON the first request, warmed serves before it
        assert clog.compiles_of(f"{name}.jitted") - base == 1, \
            (name, clog.state()["functions"])
        return latency

    cold_s = first_request_s("deployed_cold", warm=False)
    warm_s = first_request_s("deployed_warm", warm=True)
    print(f"first-request latency: {cold_s * 1e3:.1f} ms cold "
          f"(compile on the hot path) vs {warm_s * 1e3:.1f} ms after "
          f"warmup() (compile paid before traffic; compile log "
          f"attributes both)")

    served_base = clog.compiles_of("deployed.jitted")
    served = ModelFunction.deserialize(blob, name="deployed")
    server = ModelServer(ServeConfig(max_wait_s=0.02))
    server.register("deployed", served, batch_size=batch)
    server.warmup()   # pre-trace: no user request pays the compile

    results = {}

    def client(tid):
        # each client fires single-row requests cut from the oracle
        # batch, so every response is checkable row-for-row
        futs = [(i, server.submit(
            {served.input_names[0]: x[i:i + 1]}))
            for i in range(batch)]
        results[tid] = [(i, f.result(timeout=120)) for i, f in futs]

    clients = [threading.Thread(target=client, args=(t,))
               for t in range(3)]
    for t in clients:
        t.start()
    for t in clients:
        t.join()
    server.close()

    out_name = served.output_names[0]
    for tid, rows in results.items():
        for i, out in rows:
            np.testing.assert_allclose(out[out_name], expected[i:i + 1],
                                       rtol=1e-5, atol=1e-5)
    # the zero-retrace pin (tests/test_examples.py reads this line):
    # warmup + 12 concurrent requests through one fixed-batch export =
    # exactly ONE compile of the served program, zero after steady
    served_compiles = clog.compiles_of("deployed.jitted") - served_base
    assert served_compiles == 1, clog.state()["functions"]
    clog._override = saved_override
    m = server.metrics.as_dict()
    print(f"serve: {m['requests']} concurrent requests -> "
          f"{m['batches']} micro-batches, "
          f"fill {m['batch_fill_ratio']:.2f}, "
          f"p99 {m['latency_p99_ms']:.1f} ms, "
          f"rejections {m['rejections']}, "
          f"deadline_misses {m['deadline_misses']}, "
          f"served-path compiles {served_compiles} (exactly once; "
          f"unexpected retraces 0)")


if __name__ == "__main__":
    main()
