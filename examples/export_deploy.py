"""Export a model as StableHLO and serve it from a fresh process.

The reference broadcast frozen GraphDef bytes inside Spark task closures
to every executor (SURVEY §2.5); the TPU-era deploy form is serialized
StableHLO from ``jax.export``: params baked in, computation portable,
loadable without the model's Python code. This example exports on the
"driver", then loads and serves in a NEW python process that never
imports the zoo — exactly what a worker that only has the bytes does.

Run on CPU:
  JAX_PLATFORMS=cpu python examples/export_deploy.py
"""

import os
import subprocess
import sys
import tempfile

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from sparkdl_tpu.graph.function import ModelFunction

blob = open(sys.argv[1], "rb").read()
mf = ModelFunction.deserialize(blob, name="deployed")
x = np.load(sys.argv[2])
out = mf({mf.input_names[0]: x})
np.save(sys.argv[3], np.asarray(out[mf.output_names[0]]))
print("served", x.shape, "->", np.asarray(out[mf.output_names[0]]).shape)
"""


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")

    from sparkdl_tpu.models.zoo import getModelFunction

    # "driver": build + freeze (params baked into the program)
    mf = getModelFunction("TestNet", featurize=True)
    batch = 4
    blob = mf.export(batch_size=batch)
    print(f"exported {mf.name}: {len(blob) // 1024} KiB StableHLO")

    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, (batch, 32, 32, 3), dtype=np.uint8)
    expected = np.asarray(mf({mf.input_names[0]: x})[mf.output_names[0]])

    # "worker": a fresh process with only the bytes
    with tempfile.TemporaryDirectory(prefix="sparkdl_tpu_deploy_") as d:
        blob_p = os.path.join(d, "model.stablehlo")
        x_p = os.path.join(d, "x.npy")
        out_p = os.path.join(d, "out.npy")
        open(blob_p, "wb").write(blob)
        np.save(x_p, x)

        from sparkdl_tpu.utils.hostenv import sanitized_cpu_env
        proc = subprocess.run(
            [sys.executable, "-c", WORKER, blob_p, x_p, out_p],
            env=sanitized_cpu_env(pythonpath=REPO_ROOT),
            capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(f"worker failed:\n{proc.stderr[-2000:]}")
        print(proc.stdout.strip())
        got = np.load(out_p)

    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)
    print(f"worker output matches driver oracle "
          f"(max abs diff {np.abs(got - expected).max():.2e})")


if __name__ == "__main__":
    main()
