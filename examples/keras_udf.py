"""Expose a Keras model as a named batch UDF.

Reference: ``registerKerasImageUDF`` +
``spark.sql("SELECT my_udf(image) FROM images")``.

Run:  KERAS_BACKEND=jax python examples/keras_udf.py
"""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")  # must precede keras import

import tempfile

import numpy as np
from PIL import Image

import sparkdl_tpu
from sparkdl_tpu.udf import callUDF


def main():
    import keras
    keras.utils.set_random_seed(0)

    # a user model (normally loaded from .h5/.keras)
    model = keras.Sequential([
        keras.layers.Input((32, 32, 3)),
        keras.layers.Conv2D(8, 3, activation="relu"),
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dense(4, activation="softmax"),
    ])

    d = tempfile.mkdtemp(prefix="sparkdl_tpu_udf_")
    rng = np.random.default_rng(1)
    for i in range(6):
        Image.fromarray(
            rng.integers(0, 255, (48, 40, 3), dtype=np.uint8),
            "RGB").save(os.path.join(d, f"u{i}.png"))

    sparkdl_tpu.registerKerasImageUDF(
        "my_model_udf", model, preprocessor=lambda x: x / 255.0)

    df = sparkdl_tpu.readImages(d, numPartitions=2)
    out = callUDF("my_model_udf", df, "image", "probs")
    probs = out.tensor("probs")
    print("UDF output:", probs.shape, "row sums:",
          np.round(probs.sum(-1), 3))


if __name__ == "__main__":
    main()
