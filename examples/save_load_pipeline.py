"""Fit a pipeline, save it, reload it, serve identical predictions.

The reference era got ``Pipeline.save``/``load`` from pyspark ML;
sparkdl_tpu's native counterpart persists any stage — fitted or not —
to a directory (params as JSON, trained ModelFunctions as StableHLO
with weights baked in, child stages as nested saves) and
``sparkdl_tpu.load_model`` rebuilds it, including in a fresh process.

Run:  python examples/save_load_pipeline.py
"""

import os
import tempfile

import numpy as np
from PIL import Image

import sparkdl_tpu


def synthesize_dataset(n=16):
    d = tempfile.mkdtemp(prefix="sparkdl_tpu_sl_")
    rng = np.random.default_rng(4)
    rows = []
    for i in range(n):
        label = i % 2
        base = 60 if label == 0 else 190
        arr = np.clip(rng.normal(base, 25, (32, 32, 3)), 0,
                      255).astype(np.uint8)
        # zero-padded names: readImages globs in sorted order, so the
        # positional label list below stays aligned for any n
        Image.fromarray(arr, "RGB").save(os.path.join(d, f"i{i:04d}.png"))
        rows.append(label)
    return d, rows


def main():
    import pyarrow as pa

    from sparkdl_tpu.data import DataFrame

    data_dir, labels = synthesize_dataset()
    table = sparkdl_tpu.readImages(data_dir, numPartitions=2).collect()
    labeled = DataFrame.from_table(
        table.append_column("label", pa.array(labels, type=pa.int64())),
        num_partitions=2)

    pipeline = sparkdl_tpu.Pipeline(stages=[
        sparkdl_tpu.DeepImageFeaturizer(modelName="TestNet",
                                        inputCol="image",
                                        outputCol="features"),
        sparkdl_tpu.LogisticRegression(featuresCol="features",
                                       labelCol="label", maxIter=60,
                                       learningRate=0.2),
    ])
    fitted = pipeline.fit(labeled)

    save_dir = os.path.join(tempfile.mkdtemp(prefix="sparkdl_tpu_sl_"),
                            "model")
    fitted.save(save_dir)
    reloaded = sparkdl_tpu.load_model(save_dir)

    a = fitted.transform(labeled).tensor("probability")
    b = reloaded.transform(labeled).tensor("probability")
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)

    # the UNFITTED pipeline saves too (its stages nest as child saves)
    est_dir = os.path.join(tempfile.mkdtemp(prefix="sparkdl_tpu_sl_"),
                           "estimator")
    pipeline.save(est_dir)
    refit = sparkdl_tpu.load_model(est_dir).fit(labeled)
    c = refit.transform(labeled).tensor("probability")
    np.testing.assert_allclose(a, c, rtol=1e-5, atol=1e-6)

    print(f"saved → loaded → identical predictions "
          f"({a.shape[0]} rows); unfitted pipeline round-trips too")


if __name__ == "__main__":
    main()
